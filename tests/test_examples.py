"""User-facing example scripts submitted through the real CLI — the
analogue of the reference shipping runnable tony-examples and exercising
them through its e2e harness (TestTonyE2E.java:27-253). These run
``python -m tony_tpu.client.cli local`` as a genuine subprocess, exactly as
a user would, covering BASELINE.md configs 1–3."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"


def _submit(example: str, framework: str, workers: int, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
    return subprocess.run(
        [
            sys.executable, "-m", "tony_tpu.client.cli", "local",
            "--executes", str(EXAMPLES / example),
            "--framework", framework,
            "--python_binary_path", sys.executable,
            "--conf", f"tony.worker.instances={workers}",
            "--task_params", "--steps 10",
            *extra,
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )


def test_jax_example_single_worker():
    """BASELINE config 1: mini-cluster single-worker MNIST."""
    proc = _submit("mnist_distributed.py", "jax", workers=1)
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_jax_example_two_workers_dp():
    """BASELINE config 4 analogue: synchronous DP allreduce over the XLA
    collective path (gloo on CPU, ICI on a slice)."""
    proc = _submit("mnist_distributed.py", "jax", workers=2)
    assert proc.returncode == 0, proc.stderr[-2000:]


@pytest.mark.slow
def test_lm_example_trains_and_checkpoints():
    """The flagship-framework showcase: transformer LM (GQA) through
    runtime.initialize + build_job_mesh + make_train_step +
    CheckpointManager, submitted exactly as a user would."""
    proc = _submit(
        "lm_train.py", "jax", workers=1,
        extra=["--task_params",
               "--steps 8 --d-model 32 --n-layers 2 --n-heads 2 "
               "--n-kv-heads 1 --batch 4 --seq 32 --checkpoint-every 4"],
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


@pytest.mark.slow
def test_lm_generate_serves_trained_checkpoint(tmp_path):
    """The inference half: lm_train checkpoints to a shared dir, then
    lm_generate restores the TrainState through a second CLI job, builds
    a DecodeSession, and decodes — train-to-serve through the framework
    end to end (lm_generate exits 2 when no checkpoint is restorable, so
    rc 0 proves the restore happened)."""
    model_flags = ("--d-model 32 --n-layers 2 --n-heads 2 --n-kv-heads 1")
    ckpt = tmp_path / "lm-ckpt"
    train = _submit(
        "lm_train.py", "jax", workers=1,
        extra=["--task_params",
               f"--steps 8 {model_flags} --batch 4 --seq 32 "
               f"--checkpoint-every 4 --ckpt-dir {ckpt}"],
    )
    assert train.returncode == 0, train.stderr[-2000:]
    gen = _submit(
        "lm_generate.py", "jax", workers=1,
        extra=["--task_params",
               f"--ckpt {ckpt} {model_flags} --max-new 8 "
               f"--prompt 1,5,9:7,2"],
    )
    assert gen.returncode == 0, gen.stderr[-2000:]


@pytest.mark.slow
def test_lm_generate_across_topology_change(tmp_path):
    """The normal TPU lifecycle: train on MORE processes than serve. Two
    dp workers checkpoint a sharded TrainState; a ONE-process serving job
    reassembles the global params from both shard files and decodes
    (cross-topology restore — the reference's TF full-tensor checkpoints
    gave it this for free, mnist-tensorflow/mnist_distributed.py:46-48)."""
    model_flags = "--d-model 32 --n-layers 2 --n-heads 2 --n-kv-heads 1"
    ckpt = tmp_path / "lm-ckpt"
    train = _submit(
        "lm_train.py", "jax", workers=2,
        extra=["--conf", "tony.ps.instances=0",
               "--task_params",
               f"--steps 8 {model_flags} --batch 4 --seq 32 "
               f"--checkpoint-every 4 --ckpt-dir {ckpt}"],
    )
    assert train.returncode == 0, train.stderr[-2000:]
    gen = _submit(
        "lm_generate.py", "jax", workers=1,
        extra=["--conf", "tony.ps.instances=0",
               "--task_params",
               f"--ckpt {ckpt} {model_flags} --max-new 8 "
               f"--prompt 1,5,9:7,2"],
    )
    # rc 0 is the proof: lm_generate exits 2 when no checkpoint is
    # restorable, and a shape-mismatched restore raises (task stdout goes
    # to the per-task log files, not the CLI's stdout).
    assert gen.returncode == 0, gen.stderr[-2000:]


@pytest.mark.slow
def test_lm_train_streams_tokens_corpus_two_workers(tmp_path):
    """--data with a fixed-width token corpus on TWO workers: the
    flagship example trains from the framework data plane — each process
    reads its exactly-once byte-range shard and the step owns device
    placement (host batches; a pre-committed per-process device_put is
    the documented multihost trap)."""
    import numpy as np

    seq, vocab = 32, 512
    rows = np.random.default_rng(0).integers(
        1, vocab, (64, seq + 1)
    ).astype(np.uint16)
    path = tmp_path / "corpus.tokens"
    rows.tofile(path)
    proc = _submit(
        "lm_train.py", "jax", workers=2,
        extra=["--conf", "tony.ps.instances=0",
               "--task_params",
               f"--steps 8 --d-model 32 --n-layers 2 --n-heads 2 "
               f"--n-kv-heads 1 --batch 4 --seq {seq} --data {path}"],
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_lm_train_streams_jsonl_blocks_corpus(tmp_path):
    """--data with a block-compressed jsonl container: the compressed
    corpus format feeds the flagship training example end to end."""
    import numpy as np

    from tony_tpu.io import write_jsonl_blocks

    seq, vocab = 32, 512
    rng = np.random.default_rng(1)
    path = tmp_path / "corpus.jblk"
    try:
        import zstandard  # noqa: F401
        codec = "zstd"
    except ImportError:  # optional dependency; gzip is always available
        codec = "gzip"
    write_jsonl_blocks(
        str(path),
        ({"tokens": rng.integers(1, vocab, seq + 1).tolist()}
         for _ in range(64)),
        codec=codec, block_records=16,
        schema={"tokens": f"int[{seq + 1}]"},
    )
    proc = _submit(
        "lm_train.py", "jax", workers=1,
        extra=["--conf", "tony.ps.instances=0",
               "--task_params",
               f"--steps 8 --d-model 32 --n-layers 2 --n-heads 2 "
               f"--n-kv-heads 1 --batch 4 --seq {seq} --data {path}"],
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


@pytest.mark.slow
def test_jax_example_with_ps():
    """BASELINE config 2 shape: 1 ps + 2 workers through the gang barrier
    (all three run the user script, like the reference's shared-script ps
    convention; the ps process joins the collective and is untracked in
    completion accounting)."""
    proc = _submit(
        "mnist_distributed.py", "jax", workers=2,
        extra=["--conf", "tony.ps.instances=1"],
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


@pytest.mark.slow
def test_pytorch_example_ddp():
    """BASELINE config 3: PyTorch DDP-style MNIST, 2 workers over gloo."""
    proc = _submit("mnist_pytorch.py", "pytorch", workers=2)
    assert proc.returncode == 0, proc.stderr[-2000:]


@pytest.mark.slow
def test_tensorflow_example_multiworker():
    """BASELINE configs 2/4 TF shape: 2 MWMS workers + the default ps task
    serving tf.distribute.Server until the chief finishes, all wired from
    the injected TF_CONFIG. Skips (not vacuously passes) without TF."""
    import pytest

    pytest.importorskip("tensorflow")
    proc = _submit("mnist_tensorflow.py", "tensorflow", workers=2)
    assert proc.returncode == 0, proc.stderr[-2000:]


class TestCorpusBatchesUnit:
    """Direct unit coverage of lm_train's corpus_batches guards (the e2e
    tests cover the happy paths; these pin the refusal/empty-shard
    behavior without a cluster)."""

    def _args(self, tmp_path, data, batch=4, seq=8):
        import argparse
        sys.path.insert(0, str(EXAMPLES))
        try:
            import lm_train
        finally:
            sys.path.pop(0)
        ns = argparse.Namespace(
            data=data, batch=batch, seq=seq, vocab=64, steps=1
        )
        return lm_train, ns

    class _Ctx:
        process_id = 0
        num_processes = 1

    def test_mixed_suffixes_refused(self, tmp_path):
        lm_train, args = self._args(tmp_path, "a.jblk,b.tokens")
        import pytest as _pytest

        with _pytest.raises(ValueError, match="mixes"):
            next(lm_train.corpus_batches(args, self._Ctx()))

    def test_empty_path_list_refused(self, tmp_path):
        lm_train, args = self._args(tmp_path, ",")
        import pytest as _pytest

        with _pytest.raises(ValueError, match="no paths"):
            next(lm_train.corpus_batches(args, self._Ctx()))

    def test_undersized_shard_raises_not_hangs(self, tmp_path):
        import numpy as np

        rows = np.zeros((2, 9), np.uint16)  # 2 records < batch of 4
        p = tmp_path / "tiny.tokens"
        rows.tofile(p)
        lm_train, args = self._args(tmp_path, str(p))
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="no full batch"):
            next(lm_train.corpus_batches(args, self._Ctx()))

    def test_jblk_missing_tokens_field_refused(self, tmp_path):
        """A jsonl-blocks corpus whose records lack 'tokens' must fail
        with a named-field ValueError, not an opaque numpy/XLA error."""
        from tony_tpu.io import write_jsonl_blocks

        p = tmp_path / "c.jblk"
        write_jsonl_blocks(str(p), [{"text": "x"} for _ in range(8)])
        lm_train, args = self._args(tmp_path, str(p))
        import pytest as _pytest

        with _pytest.raises(ValueError, match="'tokens'"):
            next(lm_train.corpus_batches(args, self._Ctx()))

    def test_jblk_wrong_token_width_refused(self, tmp_path):
        """Records whose 'tokens' length != seq+1 must name the expected
        width up front instead of failing downstream at stacking."""
        from tony_tpu.io import write_jsonl_blocks

        p = tmp_path / "c.jblk"
        write_jsonl_blocks(
            str(p), [{"tokens": list(range(5))} for _ in range(8)]
        )
        lm_train, args = self._args(tmp_path, str(p))  # seq=8 -> wants 9
        import pytest as _pytest

        with _pytest.raises(ValueError, match="seq"):
            next(lm_train.corpus_batches(args, self._Ctx()))

    def test_epoch_wrap_yields_endlessly(self, tmp_path):
        import numpy as np

        rows = np.arange(8 * 9, dtype=np.uint16).reshape(8, 9)
        p = tmp_path / "c.tokens"
        rows.tofile(p)
        lm_train, args = self._args(tmp_path, str(p))
        src = lm_train.corpus_batches(args, self._Ctx())
        got = [np.asarray(next(src)) for _ in range(5)]  # > 1 epoch (2/epoch)
        assert all(b.shape == (4, 9) for b in got)
        np.testing.assert_array_equal(got[0], got[2])  # epoch determinism
