"""Unit tests for the session state machine: task tables, barrier assembly,
chief semantics, completion accounting (TonySession analogue)."""

import pytest

from tony_tpu.conf import TonyConfiguration, keys
from tony_tpu.coordinator.session import SessionStatus, TonySession


def _conf(**jobs):
    conf = TonyConfiguration()
    conf.set(keys.instances_key("worker"), 0)  # clear shipped default
    conf.set(keys.instances_key("ps"), 0)
    for job, n in jobs.items():
        conf.set(keys.instances_key(job), n)
    return conf


def test_task_tables():
    s = TonySession(_conf(worker=3, ps=2), session_id=1)
    assert {j: len(t) for j, t in s.tasks.items()} == {"worker": 3, "ps": 2}
    assert s.num_expected_registrations() == 5
    assert all(t.session_id == 1 for t in s.all_tasks())


def test_barrier_releases_only_when_all_registered():
    s = TonySession(_conf(worker=2, ps=1))
    assert s.cluster_spec() is None
    s.register_task("worker:0", "h0:1")
    s.register_task("ps:0", "h2:3")
    assert s.cluster_spec() is None  # worker:1 still missing
    s.register_task("worker:1", "h1:2")
    assert s.cluster_spec() == {"worker": ["h0:1", "h1:2"], "ps": ["h2:3"]}


def test_unknown_registration_ignored():
    s = TonySession(_conf(worker=1))
    assert s.register_task("worker:5", "h:1") is False
    assert s.register_task("junk", "h:1") is False


def test_chief_success_short_circuits_ps():
    # chief (worker:0) finishing cleanly ends the job even though ps never
    # exits (TonySession.updateSessionStatus:307-310: ps is untracked).
    s = TonySession(_conf(worker=1, ps=1))
    s.on_task_completed("worker", 0, 0)
    assert s.status is SessionStatus.SUCCEEDED


def test_non_chief_failure_fails_job():
    s = TonySession(_conf(worker=2))
    s.on_task_completed("worker", 1, 9)
    assert s.status is SessionStatus.FAILED
    assert "worker:1" in s.diagnostics


def test_chief_failure_fails_job_even_after_others_succeed():
    s = TonySession(_conf(worker=2))
    s.on_task_completed("worker", 1, 0)
    assert s.status is SessionStatus.NEW  # chief still out
    s.on_task_completed("worker", 0, 1)
    assert s.status is SessionStatus.FAILED


def test_all_workers_done_succeeds_without_chief_semantics():
    conf = _conf(worker=2, evaluator=1)
    conf.set(keys.K_CHIEF_NAME, "chief")  # no chief job configured
    s = TonySession(conf)
    s.on_task_completed("worker", 0, 0)
    s.on_task_completed("worker", 1, 0)
    assert s.status is SessionStatus.NEW  # evaluator still running
    s.on_task_completed("evaluator", 0, 0)
    assert s.status is SessionStatus.SUCCEEDED


def test_configurable_chief_identity():
    conf = _conf(master=1, worker=1)
    conf.set(keys.K_CHIEF_NAME, "master")
    s = TonySession(conf)
    assert s.is_chief("master", 0)
    assert not s.is_chief("worker", 0)


def test_failure_sticks_over_late_success():
    s = TonySession(_conf(worker=2))
    s.on_task_completed("worker", 1, 1)
    s.on_task_completed("worker", 0, 0)  # chief ok, but session already failed
    assert s.status is SessionStatus.FAILED


def test_kill():
    s = TonySession(_conf(worker=1))
    s.kill("user abort")
    assert s.status is SessionStatus.KILLED
    assert s.training_finished()
