"""Distributed MNIST in TensorFlow, submitted through tony_tpu with
``--framework tensorflow`` — the analogue of the reference's
tony-examples/mnist-tensorflow/mnist_distributed.py:188-220.

The executor's TensorFlowRuntime injects a byte-compatible ``TF_CONFIG``
(plus ``CLUSTER_SPEC``), so ``tf.distribute`` strategies construct their
cluster resolvers with no arguments. This example uses
MultiWorkerMirroredStrategy (the modern replacement for the reference
example's PS/replica_device_setter graph code); run 1 ps + N workers with
ParameterServerStrategy if you want the reference's exact topology.

``ps`` tasks start a ``tf.distribute.Server`` and join (the reference
example's ``server.join()`` pattern) — they serve until the chief finishes
and the coordinator reaps them (ps is untracked in completion accounting).
Workers then run MWMS over the worker subcluster. The script exits 0 with
a notice when TF is absent so submissions degrade gracefully on jax-only
images. Submit::

    python -m tony_tpu.client.cli local \
        --executes examples/mnist_tensorflow.py \
        --framework tensorflow \
        --conf tony.worker.instances=2
"""
from __future__ import annotations

import json
import os
import sys

import numpy as np


def synthetic_mnist(seed: int, n: int = 4096):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=(n,))
    images = rng.normal(0.0, 0.3, size=(n, 28, 28, 1)).astype(np.float32)
    for i, lbl in enumerate(labels):
        r, c = divmod(int(lbl), 4)
        images[i, 4 + 5 * r: 9 + 5 * r, 4 + 6 * c: 10 + 6 * c, 0] += 1.5
    return images, labels.astype(np.int64)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=64,
                    help="number of training batches")
    args = ap.parse_args()
    try:
        import tensorflow as tf
    except ImportError:
        print("tensorflow not installed; TF example skipped "
              "(TF_CONFIG was injected: %s)"
              % bool(os.environ.get("TF_CONFIG")), flush=True)
        return 0

    tf_config = json.loads(os.environ.get("TF_CONFIG", "{}"))
    print(f"TF_CONFIG: {tf_config}", flush=True)
    task = tf_config.get("task", {})
    if task.get("type") == "ps":
        # Parameter servers serve until the session ends (the reference
        # example's server.join(); the coordinator reaps ps when the chief
        # finishes — ps is untracked in completion accounting).
        server = tf.distribute.Server(
            tf.train.ClusterSpec(tf_config["cluster"]),
            job_name="ps", task_index=int(task.get("index", 0)),
        )
        # join() never returns — the coordinator reaps ps processes after
        # the chief finishes (ps is untracked in completion accounting).
        server.join()  # tony: noqa[TONY-T006] — ps serves until the coordinator reaps it; never returns by design
        raise AssertionError("tf.distribute.Server.join() returned")
    cluster = dict(tf_config.get("cluster", {}))
    if "ps" in cluster:
        # MWMS spans workers only; ps entries would make it wait on hosts
        # that never join the collective.
        cluster.pop("ps")
        tf_config["cluster"] = cluster
        os.environ["TF_CONFIG"] = json.dumps(tf_config)
    if cluster:
        strategy = tf.distribute.MultiWorkerMirroredStrategy()
    else:
        strategy = tf.distribute.get_strategy()  # standalone run
    images, labels = synthetic_mnist(seed=0)

    # Explicit distributed train loop (Keras 3 dropped model.fit support
    # for MultiWorkerMirroredStrategy): per-replica grads all-reduced to a
    # mean, SGD applied in place — the same hand-rolled shape as the
    # reference's examples.
    with strategy.scope():
        init = tf.random.stateless_normal
        w1 = tf.Variable(init((784, 128), seed=(0, 1)) * 0.05)
        b1 = tf.Variable(tf.zeros((128,)))
        w2 = tf.Variable(init((128, 10), seed=(0, 2)) * 0.05)
        b2 = tf.Variable(tf.zeros((10,)))
    trainable = (w1, b1, w2, b2)

    @tf.function
    def train_step(dist_x, dist_y):
        def replica_fn(x, y):
            with tf.GradientTape() as tape:
                flat = tf.reshape(x, (tf.shape(x)[0], -1))
                h = tf.nn.relu(flat @ w1 + b1)
                logits = h @ w2 + b2
                loss = tf.reduce_mean(
                    tf.nn.sparse_softmax_cross_entropy_with_logits(
                        labels=y, logits=logits
                    )
                )
            grads = tape.gradient(loss, trainable)
            ctx = tf.distribute.get_replica_context()
            if ctx is not None:
                grads = [
                    ctx.all_reduce(tf.distribute.ReduceOp.MEAN, g)
                    for g in grads
                ]
            for var, g in zip(trainable, grads):
                var.assign_sub(0.01 * g)
            return loss

        per_replica = strategy.run(replica_fn, args=(dist_x, dist_y))
        return strategy.reduce(
            tf.distribute.ReduceOp.MEAN, per_replica, axis=None
        )

    ds = (
        tf.data.Dataset.from_tensor_slices((images, labels))
        .batch(64).take(args.steps)
    )
    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = (
        tf.data.experimental.AutoShardPolicy.DATA
    )
    dist_ds = strategy.experimental_distribute_dataset(
        ds.with_options(options)
    )
    loss = float("nan")
    for step, (x, y) in enumerate(dist_ds):
        loss = float(train_step(x, y))
        if step % 20 == 0:
            print(f"step {step}: loss={loss:.4f}", flush=True)
    print(f"final loss={loss:.4f}", flush=True)
    return 0 if np.isfinite(loss) else 1


if __name__ == "__main__":
    sys.exit(main())
