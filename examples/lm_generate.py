"""Flagship transformer LM serving/generation, submitted through
tony_tpu — the inference half of the lm_train showcase. Restores a
checkpoint written by ``lm_train.py`` (local dir or ``gs://`` prefix),
builds a persistent ``DecodeSession`` (weights fuse once; every
``generate`` call reuses the compiled loop), and decodes continuations
for a batch of prompts with greedy or temperature sampling.

Submit locally (mini-cluster, CPU)::

    python -m tony_tpu.client.cli local \
        --executes examples/lm_generate.py --framework jax \
        --conf tony.worker.instances=1 \
        --task_params "--max-new 16 --d-model 64 --n-layers 2"

Point ``--ckpt`` at a training job's checkpoint dir to serve trained
weights (the model flags must match the training config); without it the
example smoke-runs on fresh weights. On TPU pass ``--dtype bfloat16``.
"""
from __future__ import annotations

import argparse
import sys

import jax
import jax.numpy as jnp
import numpy as np

import tony_tpu.runtime as rt
from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.models import DecodeSession, init_params


def parse_args(argv):
    p = argparse.ArgumentParser(description="tony_tpu LM generation example")
    p.add_argument("--ckpt", default="",
                   help="checkpoint dir/gs:// prefix from lm_train.py "
                        "(empty: fresh weights smoke run)")
    p.add_argument("--prompt", default="1,5,9,2",
                   help="comma-separated token ids; ':' separates batch "
                        "rows (shell-safe — task params pass through "
                        "bash -c)")
    p.add_argument("--max-new", type=int, default=16)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=1.0)
    p.add_argument("--eos", type=int, default=-1)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-seq", type=int, default=256)
    # Model flags shared with lm_train.py (same names, same defaults) —
    # they must match the checkpoint's training config.
    from lm_train import add_model_args

    add_model_args(p)
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    ctx = rt.initialize()
    # Shared derivation: a checkpoint written by lm_train.py restores
    # here only if the arg→config mapping is byte-identical.
    from lm_train import model_config_from_args

    cfg = model_config_from_args(args, max_seq=args.max_seq)
    mesh = rt.build_job_mesh()
    if not args.ckpt:
        params = init_params(jax.random.key(args.seed), cfg)
    else:
        # lm_train checkpoints the full TrainState (params + optimizer
        # state), so the restore template must have that structure — the
        # serving job keeps only .params. NOT wrapped in Path(): gs://
        # URIs must survive verbatim. The restore is topology-portable:
        # a checkpoint written on MORE (or fewer) processes than this
        # serving job reassembles from all shard files and re-shards.
        from tony_tpu.models import make_train_step

        init_fn, _ = make_train_step(cfg, mesh, learning_rate=1e-2)
        mgr = CheckpointManager(
            args.ckpt, process_id=ctx.process_id,
            num_processes=ctx.num_processes,
        )
        with jax.sharding.set_mesh(mesh):
            template = init_fn(jax.random.key(0))
            restored = mgr.restore(template)
        if restored is None:
            print(f"no complete checkpoint under {args.ckpt}",
                  file=sys.stderr)
            return 2
        params = restored.params
        print(f"restored step {int(restored.step)} from {args.ckpt}",
              flush=True)

    rows = [
        [int(t) for t in row.split(",") if t.strip()]
        for row in args.prompt.split(":")
    ]
    width = max(len(r) for r in rows)
    # Left-pad ragged prompts with token 0 so the batch is rectangular
    # (position 0 padding attends causally like a BOS run).
    prompt = jnp.asarray(
        [[0] * (width - len(r)) + r for r in rows], jnp.int32
    )

    # Serve sharded in place when the job mesh is bigger than one device
    # (fused weights megatron-split over tp, KV cache sharded); a 1-device
    # mesh serves exactly like the plain session.
    session = DecodeSession(
        params, cfg, mesh=mesh if mesh.devices.size > 1 else None
    )
    out = session.generate(
        prompt, max_new_tokens=args.max_new,
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        eos_token=None if args.eos < 0 else args.eos,
        key=(jax.random.key(args.seed)
             if args.temperature > 0 else None),
    )
    if ctx.num_processes > 1:
        # Multi-process job: `out` is a global array whose shards live on
        # other hosts too — fetching it directly raises. Gather the full
        # value onto every host first.
        from jax.experimental import multihost_utils

        out_rows = np.asarray(
            multihost_utils.process_allgather(out, tiled=True)
        )
    else:
        out_rows = np.asarray(out)
    for i, row in enumerate(out_rows):
        print(f"generated[{i}]: {','.join(str(int(t)) for t in row)}",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
