"""Distributed MNIST in JAX, submitted through tony_tpu — the TPU-native
analogue of the reference's user-facing examples
(tony-examples/mnist-tensorflow/mnist_distributed.py:188-220 and
mnist-pytorch/mnist_distributed.py:185-214).

Where the reference scripts hand-parse TF_CONFIG / RANK / INIT_METHOD, this
script makes exactly one framework call before touching devices::

    ctx = tony_tpu.runtime.initialize()

and then trains data-parallel with ``jax.pmap`` + ``jax.lax.psum`` (pure XLA
collectives — ICI on a TPU slice, gloo on the CPU backend; no NCCL, no
TF_CONFIG). Every process computes gradients on its own shard of the data
and the psum keeps replicas in lockstep.

The dataset is synthetic MNIST (deterministic from a seed): this image has
zero network egress, and the example's point is the distributed mechanics,
not digit accuracy. Swap ``synthetic_mnist`` for a real loader in practice.

Submit it locally (mini-cluster; 2 data-parallel workers)::

    python -m tony_tpu.client.cli local \
        --executes examples/mnist_distributed.py \
        --framework jax \
        --conf tony.worker.instances=2 \
        --task_params "--steps 30"
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Keep the example runnable on shared dev machines: if the ambient env pins
# JAX elsewhere, the submitter decides the platform via --shell_env.
import jax
import jax.numpy as jnp
import numpy as np
import optax

import tony_tpu.runtime as rt


def synthetic_mnist(seed: int, n: int = 4096):
    """Deterministic MNIST-shaped data: 28x28 images whose class signal is a
    bright patch at a label-dependent position (learnable, egress-free)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=(n,))
    images = rng.normal(0.0, 0.3, size=(n, 28, 28, 1)).astype(np.float32)
    for i, lbl in enumerate(labels):
        r, c = divmod(int(lbl), 4)
        images[i, 4 + 5 * r: 9 + 5 * r, 4 + 6 * c: 10 + 6 * c, 0] += 1.5
    return images, labels.astype(np.int32)


def init_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (784, 128)) * 0.05,
        "b1": jnp.zeros(128),
        "w2": jax.random.normal(k2, (128, 10)) * 0.05,
        "b2": jnp.zeros(10),
    }


def loss_fn(params, images, labels):
    x = images.reshape(images.shape[0], -1)
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    onehot = jax.nn.one_hot(labels, 10)
    loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), axis=-1))
    acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    return loss, acc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch_size", type=int, default=64,
                    help="per-device batch size")
    ap.add_argument("--learning_rate", type=float, default=1e-2)
    ap.add_argument("--working_dir", default=os.environ.get("TONY_LOG_DIR", "."),
                    help="where the chief writes final metrics")
    ap.add_argument("--checkpoint_dir", default="",
                    help="enable save/resume via tony_tpu.checkpoint "
                         "(sessions retried by the coordinator resume "
                         "from the latest complete step)")
    args = ap.parse_args()

    # The one framework call: no-op standalone, jax.distributed when the
    # executor injected a coordinator (runtime.py:57-71).
    ctx = rt.initialize()
    n_local = jax.local_device_count()
    print(
        f"[{ctx.job_name}:{ctx.task_index}] process {ctx.process_id}/"
        f"{ctx.num_processes}, {n_local} local / {jax.device_count()} global "
        f"devices, platform={jax.devices()[0].platform}",
        flush=True,
    )

    # Shard the data by process, then by local device (true DP sharding).
    images, labels = synthetic_mnist(seed=0)
    images = images[ctx.process_id:: max(ctx.num_processes, 1)]
    labels = labels[ctx.process_id:: max(ctx.num_processes, 1)]

    tx = optax.sgd(args.learning_rate, momentum=0.9)
    params = init_params(jax.random.key(0))
    opt_state = tx.init(params)
    # Replicate across local devices; psum keeps replicas identical.
    replicate = lambda tree: jax.tree.map(
        lambda x: jnp.stack([x] * n_local), tree
    )
    params, opt_state = replicate(params), replicate(opt_state)

    def train_step(params, opt_state, images, labels):
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, images, labels
        )
        grads = jax.lax.pmean(grads, "batch")  # the DP allreduce
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss, acc

    p_train_step = jax.pmap(train_step, axis_name="batch")

    # Optional checkpoint/resume: the framework half of the AM-retry
    # resume contract (a retried session restores and continues).
    mgr = None
    start_step = 0
    if args.checkpoint_dir:
        from tony_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(
            args.checkpoint_dir,
            process_id=ctx.process_id,
            num_processes=max(ctx.num_processes, 1),
        )
        restored = mgr.restore({"params": params, "opt_state": opt_state,
                                "step": jnp.zeros((), jnp.int32)})
        if restored is not None:
            params, opt_state = restored["params"], restored["opt_state"]
            start_step = int(restored["step"])
            print(f"resumed from checkpoint step {start_step}", flush=True)

    if start_step >= args.steps:
        print(f"training already complete at step {start_step}", flush=True)
        return 0

    per_step = args.batch_size * n_local
    t0 = time.time()
    loss = acc = float("nan")
    for step in range(start_step, args.steps):
        lo = (step * per_step) % (len(images) - per_step or 1)
        bi = images[lo: lo + per_step].reshape(
            n_local, args.batch_size, 28, 28, 1
        )
        bl = labels[lo: lo + per_step].reshape(n_local, args.batch_size)
        params, opt_state, loss_d, acc_d = p_train_step(
            params, opt_state, jnp.asarray(bi), jnp.asarray(bl)
        )
        loss, acc = float(loss_d[0]), float(acc_d[0])
        # Checkpoint cadence: every 10th step and the last one — a
        # per-step save would serialize training against the previous
        # write's fsync.
        if mgr is not None and (step % 10 == 9 or step == args.steps - 1):
            mgr.save(
                step + 1,
                {"params": params, "opt_state": opt_state,
                 "step": jnp.asarray(step + 1, jnp.int32)},
            )
        if step % 10 == 0 or step == args.steps - 1:
            print(f"step {step}: loss={loss:.4f} acc={acc:.3f}", flush=True)
    if mgr is not None:
        mgr.wait()  # async writes must be durable before exit
    elapsed = time.time() - t0

    if not np.isfinite(loss):
        print("non-finite loss", file=sys.stderr)
        return 1
    if ctx.process_id == 0:
        executed = args.steps - start_step
        metrics = {
            "final_loss": loss,
            "final_acc": acc,
            "steps": args.steps,
            "steps_per_sec": executed / max(elapsed, 1e-9),
            "num_processes": ctx.num_processes,
        }
        path = os.path.join(args.working_dir, "mnist_metrics.json")
        try:
            with open(path, "w") as f:
                json.dump(metrics, f)
            print(f"chief wrote {path}: {metrics}", flush=True)
        except OSError as exc:
            print(f"could not write metrics: {exc}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
