"""Distributed MNIST in PyTorch, submitted through tony_tpu with
``--framework pytorch`` — the analogue of the reference's
tony-examples/mnist-pytorch/mnist_distributed.py:185-214.

The executor's PyTorchRuntime injects both the legacy RANK / WORLD /
INIT_METHOD contract (TaskExecutor.java:139-150) and the modern
MASTER_ADDR / MASTER_PORT / WORLD_SIZE env, so ``init_process_group`` needs
no arguments beyond the backend. Gradients are averaged with explicit
all_reduce like the reference example (:114-122).

Synthetic MNIST (zero egress); CPU/gloo. Submit locally::

    python -m tony_tpu.client.cli local \
        --executes examples/mnist_pytorch.py \
        --framework pytorch \
        --conf tony.worker.instances=2 \
        --task_params "--steps 30"
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import torch
import torch.distributed as dist
import torch.nn as nn
import torch.nn.functional as F


def synthetic_mnist(seed: int, n: int = 4096):
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=(n,))
    images = rng.normal(0.0, 0.3, size=(n, 1, 28, 28)).astype(np.float32)
    for i, lbl in enumerate(labels):
        r, c = divmod(int(lbl), 4)
        images[i, 0, 4 + 5 * r: 9 + 5 * r, 4 + 6 * c: 10 + 6 * c] += 1.5
    return torch.from_numpy(images), torch.from_numpy(labels.astype(np.int64))


class Net(nn.Module):
    def __init__(self) -> None:
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        x = x.view(x.shape[0], -1)
        return self.fc2(F.relu(self.fc1(x)))


def average_gradients(model: nn.Module, world: int) -> None:
    """Explicit DP allreduce, as in the reference example (:114-122)."""
    for p in model.parameters():
        if p.grad is not None:
            dist.all_reduce(p.grad.data, op=dist.ReduceOp.SUM)
            p.grad.data /= world


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch_size", type=int, default=64)
    ap.add_argument("--learning_rate", type=float, default=1e-2)
    args = ap.parse_args()

    rank = int(os.environ.get("RANK", "0"))
    world = int(os.environ.get("WORLD_SIZE", os.environ.get("WORLD", "1")))
    if world > 1:
        # MASTER_ADDR/MASTER_PORT come from the runtime env; gloo on CPU.
        dist.init_process_group(backend="gloo", rank=rank, world_size=world)
    print(f"rank {rank}/{world} initialized", flush=True)

    images, labels = synthetic_mnist(seed=0)
    images, labels = images[rank::world], labels[rank::world]

    torch.manual_seed(0)
    model = Net()
    opt = torch.optim.SGD(model.parameters(), lr=args.learning_rate,
                          momentum=0.9)
    loss = float("nan")
    for step in range(args.steps):
        lo = (step * args.batch_size) % (len(images) - args.batch_size or 1)
        x = images[lo: lo + args.batch_size]
        y = labels[lo: lo + args.batch_size]
        opt.zero_grad()
        out = model(x)
        loss_t = F.cross_entropy(out, y)
        loss_t.backward()
        if world > 1:
            average_gradients(model, world)
        opt.step()
        loss = float(loss_t)
        if step % 10 == 0 or step == args.steps - 1:
            acc = float((out.argmax(1) == y).float().mean())
            print(f"rank {rank} step {step}: loss={loss:.4f} acc={acc:.3f}",
                  flush=True)

    if world > 1:
        dist.destroy_process_group()
    return 0 if np.isfinite(loss) else 1


if __name__ == "__main__":
    sys.exit(main())
