"""Flagship transformer LM training, submitted through tony_tpu — the
"switching from the reference" showcase: everything the orchestrator
injects (distributed identity, slice topology, data sharding, scratch
dirs) plus everything the compute plane provides (5-axis mesh, flash
attention, GQA, optional MoE, checkpoint/resume) in one user script.

The whole framework surface a training job needs:

    ctx  = rt.initialize()        # jax.distributed from the injected env
    mesh = rt.build_job_mesh()    # 5-axis mesh; dp spans slices on DCN
    reader = rt.sharded_reader([...], fmt="tokens")   # exactly-once shards
    init_fn, step_fn = make_train_step(cfg, mesh)     # jitted sharded step
    mgr = CheckpointManager(...)  # async, per-process-sharded, resumable

Submit locally (mini-cluster, CPU)::

    python -m tony_tpu.client.cli local \
        --executes examples/lm_train.py --framework jax \
        --conf tony.worker.instances=1 \
        --task_params "--steps 10 --d-model 64 --n-layers 2"

On a TPU fleet, add ``tony.gcp.project`` / ``gs://`` staging (see
docs/DEPLOY.md §3) and size the model/axes for the slice.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

import tony_tpu.runtime as rt
from tony_tpu import observability
from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.models import TransformerConfig, make_train_step
from tony_tpu.parallel.mesh import MeshSpec


def add_model_args(p: argparse.ArgumentParser) -> None:
    """Model flags shared verbatim with lm_generate.py — one definition
    so a checkpoint trained with defaults always restores with defaults
    (flag-default drift surfaces as opaque pytree mismatches)."""
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--n-heads", type=int, default=4)
    p.add_argument("--n-kv-heads", type=int, default=2)
    p.add_argument("--n-experts", type=int, default=0)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--dtype", default="float32",
                   help="float32 on CPU, bfloat16 on TPU")


def parse_args(argv):
    p = argparse.ArgumentParser(description="tony_tpu flagship LM example")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--data", default="",
                   help="corpus path(s, comma-sep): *.jblk = block-"
                        "compressed jsonl containers with a 'tokens' "
                        "field per record; anything else = fixed-width "
                        "uint16 token records of length seq+1. Empty: "
                        "the synthetic motif corpus.")
    add_model_args(p)
    p.add_argument("--checkpoint-every", type=int, default=10)
    p.add_argument("--ckpt-dir", default="",
                   help="checkpoint dir or gs:// prefix (default: the "
                        "job's TONY_LOG_DIR scratch)")
    return p.parse_args(argv)


def model_config_from_args(args, *, max_seq: int) -> TransformerConfig:
    """The single source of the arg→config derivation: lm_generate.py
    imports this so a checkpoint written here always restores there —
    drift in head_dim/d_ff derivation would surface as opaque pytree
    mismatches at restore time."""
    return TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads,
        head_dim=max(8, args.d_model // args.n_heads),
        d_ff=args.d_model * 4, max_seq=max_seq,
        n_kv_heads=args.n_kv_heads, n_experts=args.n_experts,
        dtype=args.dtype, remat=False,
    )


def synthetic_tokens(seed: int, n_docs: int, seq: int, vocab: int):
    """Deterministic corpus: repeated n-gram motifs per doc, so the LM has
    real structure to learn without any network egress."""
    rng = np.random.default_rng(seed)
    docs = []
    for _ in range(n_docs):
        motif = rng.integers(1, vocab, size=(8,))
        reps = -(-(seq + 1) // len(motif))
        noise = rng.integers(1, vocab, size=(seq + 1,))
        doc = np.tile(motif, reps)[: seq + 1]
        mask = rng.random(seq + 1) < 0.15
        doc = np.where(mask, noise, doc)
        docs.append(doc)
    return np.stack(docs).astype(np.int32)


def corpus_batches(args, ctx):
    """Endless [batch, seq+1] HOST token batches. With ``--data``,
    records stream through the framework data plane —
    ``rt.sharded_reader`` shards byte ranges exactly once across
    processes (its fetcher thread read-ahead overlaps decode with the
    running step), and the reader re-opens per epoch. Batches stay on
    the host deliberately: the train step's ``_to_global_batch`` owns
    device placement, and it is the only placement that is correct on
    BOTH single- and multi-process meshes (a pre-committed global array
    here would hit the documented multihost device_put trap). Without
    ``--data``, the synthetic motif corpus is sampled (the offline
    default)."""
    if not args.data:
        # The synthetic path honors `throttle_io` fault-plan entries the
        # same way the framework reader does (io/reader.py): the sleep
        # lands inside next(), where the step anatomy's wrap_batches
        # measures it as data_wait.
        from tony_tpu.resilience.faults import io_faults_from_env

        faults = io_faults_from_env()
        corpus = synthetic_tokens(0, n_docs=64, seq=args.seq,
                                  vocab=args.vocab)
        shard = corpus[ctx.process_id::max(ctx.num_processes, 1)]
        rng = np.random.default_rng(ctx.process_id)
        while True:
            idx = rng.integers(0, len(shard), size=(args.batch,))
            if faults is not None:
                faults.maybe_throttle()
            yield shard[idx]
        return
    paths = [p for p in args.data.split(",") if p]
    if not paths:
        raise ValueError("--data given but no paths parsed from it")
    jblk = [p.endswith(".jblk") for p in paths]
    if any(jblk) and not all(jblk):
        # A .jblk container fed to the fixed-width reader decodes
        # compressed bytes as token ids — garbage that trains without
        # erroring. Refuse the ambiguity.
        raise ValueError(
            f"--data mixes .jblk containers with raw token files: {paths}"
        )
    checked = False
    while True:  # one reader per epoch; splits re-shard identically
        yielded = 0
        if all(jblk):
            with rt.sharded_reader(
                paths, fmt="jsonl-blocks", batch_size=args.batch
            ) as r:
                for recs in r:
                    if not checked and recs:
                        # Validate the first record once, up front: a
                        # missing 'tokens' field or a ragged/wrong-width
                        # list would otherwise surface as an opaque numpy
                        # object-array or XLA shape error mid-training.
                        first = recs[0]
                        tokens = first.get("tokens") if isinstance(
                            first, dict) else None
                        if tokens is None or not hasattr(tokens, "__len__"):
                            raise ValueError(
                                f"--data {args.data}: records must carry a "
                                f"'tokens' list; first record has fields "
                                f"{sorted(first) if isinstance(first, dict) else type(first).__name__}"
                            )
                        if len(tokens) != args.seq + 1:
                            raise ValueError(
                                f"--data {args.data}: 'tokens' must be "
                                f"length seq+1 = {args.seq + 1} "
                                f"(targets are inputs shifted by one); "
                                f"first record has {len(tokens)}"
                            )
                        checked = True
                    if len(recs) == args.batch:
                        yielded += 1
                        yield np.asarray(
                            [rec["tokens"] for rec in recs], np.int32
                        )
        else:
            with rt.sharded_reader(
                paths, fmt="tokens", dtype=np.uint16,
                record_len=args.seq + 1, batch_size=args.batch,
            ) as r:
                for b in r:
                    if b.shape[0] == args.batch:
                        yielded += 1
                        yield b
        if not yielded:
            # This process's byte-range shard holds less than one full
            # batch: re-opening forever would hang training silently.
            raise RuntimeError(
                f"--data {args.data}: process {ctx.process_id}'s shard "
                f"yielded no full batch of {args.batch} (corpus too "
                f"small for this process count / batch size)"
            )


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    ctx = rt.initialize()
    mesh = rt.build_job_mesh()
    print(f"[{ctx.job_name}:{ctx.task_index}] process {ctx.process_id}/"
          f"{ctx.num_processes} slice {ctx.slice_index}/{ctx.num_slices} "
          f"mesh {dict(mesh.shape)}", flush=True)

    cfg = model_config_from_args(args, max_seq=args.seq + 1)
    init_fn, step_fn = make_train_step(cfg, mesh, learning_rate=1e-2)

    # Per-process corpus shard via the framework's exactly-once sharding
    # identity (the py4j-reader analogue) — file-backed with --data,
    # synthetic otherwise. The step's anatomy recorder wraps the
    # iterator so host time blocked on input reads as the data_wait
    # phase (tony_step_phase_ms{phase="data_wait"}) even on the
    # synthetic path that never touches the tony_io_* telemetry.
    batches = corpus_batches(args, ctx)
    stats = getattr(step_fn, "stepstats", None)
    if stats is not None:
        batches = stats.wrap_batches(batches)

    scratch = os.environ.get("TONY_LOG_DIR", ".")
    # NOT wrapped in Path(): --ckpt-dir / TONY_CHECKPOINT_DIR may be a
    # gs:// prefix. TONY_CHECKPOINT_DIR is the coordinator-probed location
    # (tony.checkpoint.location) — using it keeps resume-step export and
    # progress-aware retry budgets working without per-script flags.
    ckpt_dir = (
        args.ckpt_dir
        or os.environ.get("TONY_CHECKPOINT_DIR")
        or os.path.join(scratch, "lm-checkpoints")
    )
    mgr = CheckpointManager(
        ckpt_dir,
        process_id=ctx.process_id, num_processes=ctx.num_processes,
    )
    with jax.sharding.set_mesh(mesh):
        state = init_fn(jax.random.key(0))
        # Checkpoint-aware restart: a retried session is told the newest
        # step the coordinator saw complete (TONY_RESUME_STEP);
        # restore_resumable pins every process to that SAME step, falling
        # back to newest-complete outside a retry.
        restored = mgr.restore_resumable(state)
        if restored is not None:
            state = restored
            print(f"resumed from step {int(state.step)}", flush=True)
        first = last = None
        if int(state.step) >= args.steps:
            # A retried session can resume a checkpoint already at the
            # target: that is success, not a crash.
            print(f"already at step {int(state.step)} >= {args.steps}; "
                  f"nothing to do", flush=True)
            return 0
        # `degrade_task` fault-plan entries make THIS process a
        # deterministic mid-training straggler (incarnation 0 only — a
        # replacement after a healing eviction runs clean).
        from tony_tpu.resilience.faults import step_faults_from_env

        step_faults = step_faults_from_env()
        # Host-side step mirror: the in-jit counter advances by exactly
        # one per dispatch, so tracking it here keeps the loop condition
        # and every consumer below off the device — the loss fence is
        # the step's ONE intended readback (TONY-X002 polices the rest).
        step = int(state.step)
        while step < args.steps:
            tokens = next(batches)
            t0 = time.perf_counter()
            state, metrics = step_fn(state, tokens)
            loss = float(jax.device_get(metrics["loss"]))  # tony: noqa[TONY-X002] — the step's intended readback fence
            step += 1
            if step_faults is not None:
                step_faults.maybe_degrade(step)
            # The float() above is the readback fence, so this wall time
            # covers the whole step. report() publishes the snapshot to
            # TONY_METRICS_FILE (when tony launched us), where the
            # executor piggybacks it on its heartbeat — live loss and
            # throughput on the coordinator's /metrics, no extra RPCs.
            dt = time.perf_counter() - t0
            first = loss if first is None else first
            last = loss
            report = {
                "step": step, "loss": loss,
                "tokens_per_sec": args.batch * args.seq / dt if dt else 0.0,
            }
            if stats is None or not stats.enabled \
                    or not stats.steps_observed:
                # With step anatomy active, stepstats owns step_time_ms
                # (the dispatch-to-dispatch wall its phases sum to —
                # two writers with two wall definitions would fight
                # over one gauge). Until it has actually published one
                # (it drops the compile interval, so nothing before the
                # 3rd dispatch), this fenced wall keeps the gauge fed —
                # a 2-step smoke job must still report step times.
                report["step_time_ms"] = dt * 1000.0
            observability.report(**report)
            if step % 5 == 0 or step == args.steps:
                print(f"step {step}: loss {loss:.4f}", flush=True)
            # Interval saves, plus the coordinator's live-migration /
            # evict-time flush order (TONY_CKPT_FLUSH_FILE, relayed by
            # the executor off its heartbeat reply): the coordinator is
            # waiting on this save's commit marker before tearing the
            # job down, so the relaunch resumes from THIS step instead
            # of one checkpoint interval back. flush_requested is
            # checked FIRST (not behind a short-circuit `or`): an
            # interval save at/past the target must also CONSUME the
            # order, or the next step would save a second time for
            # nothing.
            flushed = mgr.flush_requested(step)
            if flushed or step % args.checkpoint_every == 0:
                mgr.save(step, state)
        mgr.save(step, state, blocking=True)

    if not np.isfinite(last) or not last < first:
        print(f"loss did not descend: {first} -> {last}", file=sys.stderr)
        return 1
    print(f"done: loss {first:.4f} -> {last:.4f}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
