"""Long-lived LM serving task — the ``serving`` task type's user script.

Restores a checkpoint written by ``lm_train.py`` (local dir or ``gs://``
prefix), fuses it once through ``DecodeSession`` (so the persistent
compile cache recognizes the program on restart), and serves generate
requests over HTTP through the continuous-batching engine
(``tony_tpu.serving``): iteration-level scheduling over a fixed slot
batch, chunked prefill, EOS retirement, slot reuse. Engine knobs default
from the ``TONY_SERVING_*`` env the executor exports from
``tony.serving.*`` conf.

Submit locally (mini-cluster, CPU)::

    python -m tony_tpu.client.cli local \
        --executes examples/lm_serve.py --framework jax \
        --conf tony.serving.instances=1 --conf tony.worker.instances=0 \
        --conf tony.chief.name=serving \
        --task_params "--d-model 64 --n-layers 2 --max-requests 100"

With ``tony.chief.name=serving`` the executor reserves a port, exports
it as ``TB_PORT``, and registers ``http://host:port`` with the
coordinator — so the engine's endpoint is discoverable exactly like a
notebook's, and ``ProxyServer`` (or ``tony notebook``'s tunnel) fronts
it. Clients then::

    POST /generate  {"prompt": [1,5,9], "max_new_tokens": 32,
                     "temperature": 0.0, "eos_id": 2}
    GET  /healthz   -> engine stats
    POST /shutdown  -> drain and exit 0 (job SUCCEEDs)

Serving telemetry (tony_serving_*) publishes through the observability
registry onto $TONY_METRICS_FILE, rides executor heartbeats, and shows
up on the coordinator's /metrics for the health detectors.
"""
from __future__ import annotations

import argparse
import os
import sys

import jax

import tony_tpu.runtime as rt
from tony_tpu import constants
from tony_tpu.checkpoint import CheckpointManager
from tony_tpu.models import DecodeSession, init_params
from tony_tpu.serving import ServingEngine
from tony_tpu.serving.http import ServingServer


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def parse_args(argv):
    p = argparse.ArgumentParser(description="tony_tpu LM serving example")
    p.add_argument("--ckpt", default="",
                   help="checkpoint dir/gs:// prefix from lm_train.py "
                        "(empty: fresh weights smoke run)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-seq", type=int, default=512)
    p.add_argument("--slots", type=int,
                   default=_env_int(constants.TONY_SERVING_SLOTS, 8))
    p.add_argument("--prefill-chunk", type=int,
                   default=_env_int(constants.TONY_SERVING_PREFILL_CHUNK, 32))
    p.add_argument("--decode-window", type=int,
                   default=_env_int(constants.TONY_SERVING_DECODE_WINDOW, 1))
    p.add_argument("--max-queue", type=int,
                   default=_env_int(constants.TONY_SERVING_MAX_QUEUE, 1024))
    p.add_argument("--port", type=int, default=-1,
                   help="HTTP port; -1 = $TB_PORT (chief-registered URL) "
                        "else $TONY_SERVING_PORT else ephemeral")
    p.add_argument("--addr-file", default="",
                   help="write host:port here once listening (empty: "
                        "$TONY_LOG_DIR/serving-<job>-<idx>.addr when "
                        "tony-launched)")
    p.add_argument("--max-requests", type=int, default=0,
                   help="exit 0 after this many retired requests "
                        "(0 = serve until /shutdown)")
    p.add_argument("--models", action="append", default=[],
                   help="extra resident checkpoint as name=ckpt_dir "
                        "(repeatable); requests route by their 'model' "
                        "field, swapped compile-free at idle batch "
                        "boundaries (DecodeSession identity layout)")
    p.add_argument("--max-resident-models", type=int, default=4,
                   help="LRU bound on host-resident model packs")
    p.add_argument("--role", choices=("both", "prefill", "decode"),
                   default="both",
                   help="disaggregated fleet role advertised on "
                        "/healthz (the router enforces it; the engine "
                        "itself can always do both)")
    # Model flags shared with lm_train.py (same names, same defaults) —
    # they must match the checkpoint's training config.
    from lm_train import add_model_args

    add_model_args(p)
    return p.parse_args(argv)


def _resolve_port(args) -> int:
    if args.port >= 0:
        return args.port
    tb = os.environ.get(constants.TB_PORT)
    if tb:
        return int(tb)
    return _env_int(constants.TONY_SERVING_PORT, 0)


def _addr_file(args) -> str:
    if args.addr_file:
        return args.addr_file
    log_dir = os.environ.get(constants.TONY_LOG_DIR)
    if not log_dir:
        return ""
    job = os.environ.get(constants.JOB_NAME, "serving")
    idx = os.environ.get(constants.TASK_INDEX, "0")
    return os.path.join(log_dir, f"serving-{job}-{idx}.addr")


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    ctx = rt.initialize()
    from lm_train import model_config_from_args

    cfg = model_config_from_args(args, max_seq=args.max_seq)
    mesh = rt.build_job_mesh()
    if not args.ckpt:
        params = init_params(jax.random.key(args.seed), cfg)
    else:
        # Same restore contract as lm_generate.py: the training job
        # checkpoints the full TrainState; serving keeps only .params.
        from tony_tpu.models import make_train_step

        init_fn, _ = make_train_step(cfg, mesh, learning_rate=1e-2)
        mgr = CheckpointManager(
            args.ckpt, process_id=ctx.process_id,
            num_processes=ctx.num_processes,
        )
        with jax.sharding.set_mesh(mesh):
            template = init_fn(jax.random.key(0))
            restored = mgr.restore(template)
        if restored is None:
            print(f"no complete checkpoint under {args.ckpt}",
                  file=sys.stderr)
            return 2
        params = restored.params
        print(f"restored step {int(restored.step)} from {args.ckpt}",
              flush=True)

    # Fuse once through DecodeSession (compile-cache-keyed like every
    # other Plan-instrumented program), then hand the fused pack to the
    # engine — a serving restart on a warm persistent cache skips the
    # fusion AND both engine executables' XLA compiles.
    session = DecodeSession(params, cfg)
    engine = ServingEngine(
        session.params, cfg, slots=args.slots,
        prefill_chunk=args.prefill_chunk,
        decode_window=args.decode_window, max_queue=args.max_queue,
        seed=args.seed, max_resident_models=args.max_resident_models,
    )
    # Multiplexed checkpoints: every --models name=ckpt registers a lazy
    # loader — restore happens off the engine loop on first routed
    # request, and the swap itself is compile-free because every pack
    # shares the DecodeSession identity layout.
    for entry in args.models:
        mname, _, mdir = entry.partition("=")
        if not mname or not mdir:
            print(f"bad --models entry {entry!r} (want name=ckpt_dir)",
                  file=sys.stderr)
            return 2

        def _load(ckpt_dir=mdir):
            from tony_tpu.models import make_train_step

            m_init, _ = make_train_step(cfg, mesh, learning_rate=1e-2)
            m_mgr = CheckpointManager(
                ckpt_dir, process_id=ctx.process_id,
                num_processes=ctx.num_processes,
            )
            with jax.sharding.set_mesh(mesh):
                m_restored = m_mgr.restore(m_init(jax.random.key(0)))
            if m_restored is None:
                raise FileNotFoundError(
                    f"no complete checkpoint under {ckpt_dir}")
            return m_restored.params

        engine.add_model(mname, loader=_load)
    engine.start()
    server = ServingServer(engine, port=_resolve_port(args),
                           extra_health={"role": args.role})
    port = server.start()
    addr_file = _addr_file(args)
    if addr_file:
        # Atomic publish: a poller must never read a torn half-line.
        tmp = f"{addr_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"127.0.0.1:{port}\n")
        os.replace(tmp, addr_file)
    print(f"serving on :{port} (slots={args.slots}, "
          f"chunk={args.prefill_chunk})", flush=True)
    try:
        while not server.wait_shutdown(timeout=0.2):
            if (args.max_requests
                    and engine.stats()["retired"] >= args.max_requests):
                break
    finally:
        # Graceful: stop admitting, let in-flight streams retire (a
        # client mid-long-poll gets its completed generation, not an
        # error), THEN tear down.
        engine.drain(timeout=60.0)
        server.stop()
        engine.close()
    print(f"serving done: {engine.stats()}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
